"""The flat (slot-per-pod) engine vs the exact heap engine.

Contract (fks_tpu/sim/flat.py module docstring):
- on runs with ZERO failed placements the two engines are BIT-IDENTICAL
  (pop order is fully determined by unique (time, tie_rank) keys there);
- on runs with retries only retry TIMING may differ (the flat engine uses
  time-order next-deletion, the exact engine replicates the reference's
  heap-array-order scan); placement rules, refunds, fragmentation scoring,
  snapshot overshoot and fitness arithmetic are shared;
- the default trace's reference policies stay close (scheduled counts
  equal, fitness within a documented tolerance).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fks_tpu.data.build import make_workload
from fks_tpu.models import zoo
from fks_tpu.sim import flat
from fks_tpu.sim.engine import SimConfig, simulate
from tests.test_engine_micro import micro_workload


def _assert_results_equal(a, b):
    for name, va, vb in zip(a._fields, a, b):
        np.testing.assert_array_equal(
            np.asarray(va), np.asarray(vb), err_msg=name)


def _roomy_workload(num_pods=40, seed=0):
    """A workload where every pod always fits -> zero failed placements."""
    rng = np.random.default_rng(seed)
    nodes = [{"node_id": f"n{i}", "cpu_milli": 64000, "memory_mib": 262144,
              "gpus": [1000] * 8, "gpu_memory_mib": 16384} for i in range(4)]
    pods = [{"pod_id": f"pod-{i:04d}",
             "cpu_milli": int(rng.integers(100, 1500)),
             "memory_mib": int(rng.integers(100, 4000)),
             "num_gpu": int(rng.integers(0, 3)),
             "gpu_milli": int(rng.integers(1, 300)),
             "creation_time": int(rng.integers(0, 1000)),
             "duration_time": int(rng.integers(0, 500))}
            for i in range(num_pods)]
    for p in pods:
        if p["num_gpu"] == 0:
            p["gpu_milli"] = 0
    return make_workload(nodes, pods, pad_nodes_to=4, pad_gpus_to=8,
                         pad_pods_to=64)


@pytest.mark.parametrize("policy_name", ["first_fit", "best_fit",
                                         "funsearch_4901"])
@pytest.mark.slow
def test_no_retry_run_bit_identical(policy_name):
    wl = _roomy_workload()
    cfg = SimConfig()
    pol = zoo.ZOO[policy_name]()
    exact = simulate(wl, pol, cfg)
    fastr = flat.simulate(wl, pol, cfg)
    assert int(exact.num_fragmentation_events) == 0  # premise: no failures
    _assert_results_equal(exact, fastr)


@pytest.mark.slow
def test_micro_workload_bit_identical():
    wl = micro_workload()
    for name in ("first_fit", "best_fit"):
        exact = simulate(wl, zoo.ZOO[name]())
        fastr = flat.simulate(wl, zoo.ZOO[name]())
        if int(exact.num_fragmentation_events) == 0:
            _assert_results_equal(exact, fastr)
        else:
            assert int(fastr.scheduled_pods) == int(exact.scheduled_pods)


def test_refuse_all_policy_drops_everything():
    """No deletions ever pending -> every failed pod silently drops
    (reference event_simulator.py:51-58 fall-through) -> score 0."""
    wl = _roomy_workload(num_pods=8)
    res = flat.simulate(wl, lambda pod, nodes: jnp.zeros(
        nodes.node_mask.shape[0], jnp.int32))
    assert float(res.policy_score) == 0.0
    assert int(res.scheduled_pods) == 0
    assert not bool(res.failed)
    assert not bool(res.truncated)  # queue fully drained


@pytest.mark.slow
def test_population_run_matches_single_runs():
    from fks_tpu.models import parametric

    wl = _roomy_workload(num_pods=32, seed=3)
    cfg = SimConfig()
    key = jax.random.PRNGKey(0)
    params = parametric.init_population(key, 4, noise=0.2)
    run_pop = jax.jit(flat.make_population_run_fn(wl, parametric.score, cfg))
    res = run_pop(params, flat.initial_state(wl, cfg))
    single = jax.jit(flat.make_param_run_fn(wl, parametric.score, cfg))
    s0 = flat.initial_state(wl, cfg)
    for i in range(4):
        one = single(params[i], s0)
        np.testing.assert_allclose(np.asarray(res.policy_score)[i],
                                   np.asarray(one.policy_score))
        np.testing.assert_array_equal(np.asarray(res.assigned_node)[i],
                                      np.asarray(one.assigned_node))


@pytest.mark.slow
def test_default_trace_close_to_exact(default_workload):
    """Retry timing is the ONLY divergence; on the reference trace the
    scheduled counts must match and fitness must stay within 4e-2 for the
    published policies. Measured deltas (PROFILE.md): first_fit 0.002,
    best_fit 0.013, funsearch_4901 0.029 — chaotic snowballing from single
    retry-time differences, not systematic bias."""
    cfg = SimConfig()
    # two policies bound the divergence spectrum (first_fit: 3k retries,
    # funsearch_4901: 11k — PROFILE.md); best_fit sits between, checked
    # against its golden constants below without a second exact-engine
    # run (one fewer full-trace CPU pass matters on this single core).
    for name in ("first_fit", "funsearch_4901"):
        exact = simulate(default_workload, zoo.ZOO[name](), cfg)
        fastr = flat.simulate(default_workload, zoo.ZOO[name](), cfg)
        assert int(fastr.scheduled_pods) == int(exact.scheduled_pods), name
        d = abs(float(fastr.policy_score) - float(exact.policy_score))
        assert d < 4e-2, (name, d)
    bf = flat.simulate(default_workload, zoo.ZOO["best_fit"](), cfg)
    assert int(bf.scheduled_pods) == 8152  # golden: all placed
    assert abs(float(bf.policy_score) - 0.4465) < 4e-2


def test_population_with_truncating_lane_terminates():
    """Regression: a lane that exhausts its step budget with events still
    pending (truncated) must not hold the population while_loop's cond
    true through other, finished lanes — lane_active's block-min reduction
    has to stay per-lane on the batched state."""
    from fks_tpu.models import parametric

    wl = _roomy_workload(num_pods=16, seed=5)
    cfg = SimConfig(max_steps=8)  # force truncation for every lane
    run = jax.jit(flat.make_population_run_fn(wl, parametric.score, cfg))
    res = run(parametric.init_population(jax.random.PRNGKey(0), 3, noise=0.1),
              flat.initial_state(wl, cfg))
    assert bool(np.all(np.asarray(res.truncated)))
    assert np.asarray(res.policy_score).tolist() == [0.0, 0.0, 0.0]


@pytest.mark.slow
def test_pod_count_not_block_multiple():
    """Regression: the slot queue pads itself to a whole number of blocks;
    workloads whose padded pod count is not a multiple of the block width
    (e.g. synthetic scale runs) must work, not raise."""
    wl = _roomy_workload(num_pods=40, seed=7)
    wl = make_workload(
        [{"node_id": f"n{i}", "cpu_milli": 64000, "memory_mib": 262144,
          "gpus": [1000] * 8} for i in range(4)],
        [{"pod_id": f"pod-{i:04d}", "cpu_milli": 500, "memory_mib": 500,
          "num_gpu": 0, "gpu_milli": 0, "creation_time": i,
          "duration_time": 3} for i in range(200)],
        pad_nodes_to=4, pad_gpus_to=8, pad_pods_to=200)  # 200 % 128 != 0
    exact = simulate(wl, zoo.ZOO["best_fit"]())
    fastr = flat.simulate(wl, zoo.ZOO["best_fit"]())
    _assert_results_equal(exact, fastr)
    # the opt-in audit must also handle the queue's block padding
    audited = flat.simulate(wl, zoo.ZOO["best_fit"](),
                            SimConfig(validate_invariants=True))
    assert int(audited.invariant_violations) == 0


def test_invariant_audit_clean(default_workload):
    cfg = SimConfig(validate_invariants=True)
    res = flat.simulate(default_workload, zoo.ZOO["best_fit"](), cfg)
    assert int(res.invariant_violations) == 0


@pytest.mark.slow
def test_unpacked_aux_gpus_path_bit_identical():
    """When node_bits + G > 31 the (node, gpu_bits) pair no longer fits one
    int32 aux word and the engine must fall back to a separate aux_gpus
    carry (fks_tpu/sim/flat.py _packable). Same contract as the packed
    path: bit-identical to the exact engine on retry-free runs."""
    rng = np.random.default_rng(3)
    nodes = [{"node_id": f"n{i}", "cpu_milli": 64000, "memory_mib": 262144,
              "gpus": [1000] * 30, "gpu_memory_mib": 16384} for i in range(4)]
    pods = [{"pod_id": f"pod-{i:04d}",
             "cpu_milli": int(rng.integers(100, 1500)),
             "memory_mib": int(rng.integers(100, 4000)),
             "num_gpu": int(rng.integers(0, 5)),
             "gpu_milli": int(rng.integers(1, 400)),
             "creation_time": int(rng.integers(0, 1000)),
             "duration_time": int(rng.integers(0, 500))}
            for i in range(32)]
    for p in pods:
        if p["num_gpu"] == 0:
            p["gpu_milli"] = 0
    wl = make_workload(nodes, pods, pad_nodes_to=4, pad_gpus_to=30,
                       pad_pods_to=32)
    cfg = SimConfig()
    assert not flat._packable(wl.cluster.n_padded, wl.cluster.g_padded)
    assert flat.initial_state(wl, cfg).aux_gpus is not None
    for name in ("first_fit", "best_fit"):
        exact = simulate(wl, zoo.ZOO[name](), cfg)
        fastr = flat.simulate(wl, zoo.ZOO[name](), cfg)
        assert int(exact.num_fragmentation_events) == 0
        _assert_results_equal(exact, fastr)


def test_unpacked_aux_gpus_with_contention():
    """Unpacked path under GPU contention (failed placements + retries +
    delete refunds through the separate gpu-bits carry): observables must
    stay internally consistent and the run must complete."""
    nodes = [{"node_id": "n0", "cpu_milli": 64000, "memory_mib": 262144,
              "gpus": [1000] * 30, "gpu_memory_mib": 16384}]
    # 6 pods each wanting 12 of 30 GPUs: at most 2 fit concurrently
    pods = [{"pod_id": f"pod-{i:02d}", "cpu_milli": 100, "memory_mib": 100,
             "num_gpu": 12, "gpu_milli": 900, "creation_time": i,
             "duration_time": 50} for i in range(6)]
    # pad the node axis to 4 so node_bits(2) + G(30) > 31 -> unpacked
    wl = make_workload(nodes, pods, pad_nodes_to=4, pad_gpus_to=30,
                       pad_pods_to=8)
    assert not flat._packable(wl.cluster.n_padded, wl.cluster.g_padded)
    res = flat.simulate(wl, zoo.ZOO["best_fit"](),
                        SimConfig(validate_invariants=True))
    assert int(res.invariant_violations) == 0
    assert int(res.scheduled_pods) == 6
    assert not bool(res.failed)
    # every assigned pod holds exactly num_gpu distinct GPUs
    bits = np.asarray(res.assigned_gpus)[:6]
    assert all(bin(int(b)).count("1") == 12 for b in bits)


def test_segmented_population_matches():
    """make_segmented_population_run splits the while_loop into bounded
    device calls (axon-tunnel kill-window protection); every SimResult
    field must be identical to the unsegmented runner, including with a
    segment length that forces many host round-trips and one that exceeds
    the whole run (degenerate single segment)."""
    from fks_tpu.models import parametric

    wl = _roomy_workload(num_pods=40, seed=3)
    cfg = SimConfig(track_ctime=False)
    params = parametric.init_population(jax.random.PRNGKey(2), 4, noise=0.1)
    s0 = flat.initial_state(wl, cfg)
    ref = jax.jit(flat.make_population_run_fn(wl, parametric.score, cfg))(
        params, s0)
    for seg in (7, 10_000):
        seg_run = flat.make_segmented_population_run(
            wl, parametric.score, cfg, seg_steps=seg)
        _assert_results_equal(seg_run(params, s0), ref)


def test_segmented_population_with_contention_and_truncation():
    """Segmentation must also agree when lanes fail placements (retries
    queue new events mid-run) and when the step budget truncates lanes."""
    from fks_tpu.models import parametric

    wl = micro_workload()
    cfg = SimConfig(max_steps=9)  # truncates some lanes mid-trace
    params = parametric.init_population(jax.random.PRNGKey(4), 3, noise=0.3)
    s0 = flat.initial_state(wl, cfg)
    ref = jax.jit(flat.make_population_run_fn(wl, parametric.score, cfg))(
        params, s0)
    seg_run = flat.make_segmented_population_run(
        wl, parametric.score, cfg, seg_steps=2)
    _assert_results_equal(seg_run(params, s0), ref)
