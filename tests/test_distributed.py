"""Real multi-process distributed-backend test (2 processes x 4 devices).

Round-4 verdict ask #9: the hybrid ("dcn", "pop") mesh and
``init_distributed`` had only been exercised inside ONE process (the
8-virtual-device conftest mesh). Here two REAL processes form a
``jax.distributed`` local cluster over a loopback coordinator, each
contributing 4 virtual CPU devices, and evaluate a sharded population on
the global 2x4 hybrid mesh — the same code path a multi-host TPU pod
takes (SURVEY.md §5: the reference's only inter-worker substrate is a
single-host ProcessPoolExecutor, funsearch_integration.py:535-562; this
is its cross-process equivalence test).

Checks: process group forms (process_count == 2, 8 global devices), the
sharded eval runs across the process boundary, the replicated elite
outputs AGREE between the two processes, and they match per-candidate
single-process simulation scores exactly.
"""
import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = """
import json, sys
import numpy as np

pid, port = int(sys.argv[1]), sys.argv[2]

import jax
try:  # jax 0.4.x CPU backend has no cross-process collectives built in;
    # the gloo implementation must be selected before backend init
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass  # newer jax: gloo is the default for multiprocess CPU
from fks_tpu.parallel.mesh import (
    hybrid_population_mesh, init_distributed, make_sharded_eval,
    pad_population)

n = init_distributed(f"localhost:{port}", num_processes=2, process_id=pid)
assert n == 2, f"process_count {n}"
assert jax.process_index() == pid
assert len(jax.devices()) == 8, len(jax.devices())       # global
assert len(jax.local_devices()) == 4, len(jax.local_devices())

from fks_tpu.data.build import make_workload
from fks_tpu.models import parametric
from fks_tpu.sim.engine import SimConfig, simulate

nodes = [
    {"node_id": "node1", "cpu_milli": 8000, "memory_mib": 16000,
     "gpus": [1000, 1000], "gpu_memory_mib": 8000},
    {"node_id": "node2", "cpu_milli": 4000, "memory_mib": 8000, "gpus": []},
]
pods = [
    {"pod_id": "pod1", "cpu_milli": 1000, "memory_mib": 2000, "num_gpu": 0,
     "gpu_milli": 0, "creation_time": 0, "duration_time": 10},
    {"pod_id": "pod2", "cpu_milli": 2000, "memory_mib": 4000, "num_gpu": 1,
     "gpu_milli": 500, "creation_time": 5, "duration_time": 15},
    {"pod_id": "pod3", "cpu_milli": 3000, "memory_mib": 6000, "num_gpu": 0,
     "gpu_milli": 0, "creation_time": 10, "duration_time": 8},
    {"pod_id": "pod4", "cpu_milli": 1500, "memory_mib": 3000, "num_gpu": 2,
     "gpu_milli": 400, "creation_time": 15, "duration_time": 12},
]
wl = make_workload(nodes, pods, pad_nodes_to=4, pad_gpus_to=4, pad_pods_to=8)

mesh = hybrid_population_mesh(num_slices=2)
assert mesh.axis_names == ("dcn", "pop")
assert mesh.shape["dcn"] == 2 and mesh.shape["pop"] == 4
# the outer (DCN) axis really crosses the process boundary
procs_per_row = [{d.process_index for d in row} for row in mesh.devices]
assert procs_per_row[0] != procs_per_row[1], procs_per_row

params = np.asarray(parametric.init_population(
    jax.random.PRNGKey(0), 8, noise=0.2))
params, real = pad_population(jax.numpy.asarray(params), mesh)
ev = make_sharded_eval(wl, mesh, elite_k=4, engine="exact")
scores, elite_idx, elite_scores = ev(params, real)
es = np.asarray(jax.device_get(elite_scores))    # replicated -> addressable
ei = np.asarray(jax.device_get(elite_idx))

# single-process reference: each candidate through the plain engine
ref = np.asarray([float(simulate(wl, parametric.as_policy(
    jax.numpy.asarray(params)[i])).policy_score) for i in range(8)])
want = np.sort(ref)[::-1][:4]
np.testing.assert_allclose(es, want, rtol=0, atol=0)
np.testing.assert_allclose(ref[ei], es, rtol=0, atol=0)

print("RESULT " + json.dumps({
    "process": pid, "elite_scores": es.tolist(), "elite_idx": ei.tolist()}))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_cluster(tmp_path, port):
    """Spawn the 2-process cluster on ``port``; (outs, bind_conflict).

    bind_conflict is True when a child died because the coordinator port
    was taken — _free_port closes the probe socket before the child binds
    it (TOCTOU), so another process on the host can grab it in between.
    """
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    # the axon sitecustomize would try the TPU tunnel at interpreter start
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
         if p and "axon_site" not in p] + [REPO])
    script = tmp_path / "child.py"
    script.write_text(CHILD)
    procs = [
        subprocess.Popen([sys.executable, str(script), str(i), str(port)],
                         env=env, cwd=REPO, text=True,
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for i in range(2)
    ]
    outs = []
    for i, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"process {i} timed out forming/running the cluster")
        if p.returncode != 0 and "already in use" in err.lower():
            for q in procs:
                q.kill()
            return None, True
        assert p.returncode == 0, f"process {i} failed:\n{err[-4000:]}"
        outs.append(out)
    return outs, False


@pytest.mark.slow
def test_two_process_hybrid_mesh(tmp_path):
    outs = None
    for _ in range(3):  # fresh port per attempt; see _run_cluster docstring
        outs, bind_conflict = _run_cluster(tmp_path, _free_port())
        if not bind_conflict:
            break
    else:
        pytest.fail("coordinator port stolen on 3 consecutive attempts")

    results = []
    for i, out in enumerate(outs):
        lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert lines, f"process {i} printed no result:\n{out[-2000:]}"
        results.append(json.loads(lines[-1][len("RESULT "):]))
    # both controllers computed the identical replicated elite set
    assert results[0]["elite_scores"] == results[1]["elite_scores"]
    assert results[0]["elite_idx"] == results[1]["elite_idx"]
    assert results[0]["elite_scores"][0] > 0
