"""Scenario-suite subsystem tests (fks_tpu.scenarios).

Coverage map:
- generator determinism (byte-identical regeneration from seeds)
- fault-event construction (sorting, padding, kind validation)
- cordon semantics on BOTH engines (no placement onto a downed node
  during its window; placements resume after NODE_UP; no eviction)
- golden fault fixture (tools/make_golden.py --scenario-fault): exact AND
  flat engines held to the pinned scores (<= 1e-5) and the pinned
  per-CREATE placement vector — the score is aggregate-utilization and
  invariant to WHICH node hosts a pod, so the assignment sequence is the
  pin that actually catches fault-semantics regressions
- suite registry + vmapped suite eval == per-scenario sequential evals
- mesh-sharded suite eval == unsharded population eval, elites ranked by
  the composite robust score
- aggregation math + RobustConfig validation
- CodeEvaluator / FunSearch wiring (per-scenario breakdown in records,
  champion JSON, GenerationStats) and the fused-engine rejection
- cli scenarios / schema-checker acceptance of the new record kinds
"""
import dataclasses
import json
import pathlib
import subprocess
import sys

import jax
import numpy as np
import pytest

from fks_tpu.data.build import make_workload
from fks_tpu.data.synthetic import synthetic_workload
from fks_tpu.models import parametric, zoo
from fks_tpu.obs import tracing
from fks_tpu.ops.heap import KIND_NODE_DOWN, KIND_NODE_UP
from fks_tpu.scenarios import (
    RobustConfig, ScenarioSpec, aggregate, fault_events_for, get_suite,
    list_suites, make_fault_events, make_sharded_suite_eval, make_suite_eval,
    perturb_workload,
)
from fks_tpu.sim import get_engine
from fks_tpu.sim.engine import SimConfig

FIXTURES = pathlib.Path(__file__).parent / "fixtures"
REPO = pathlib.Path(__file__).parent.parent


def _assert_trees_identical(a, b):
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for xa, xb in zip(la, lb):
        assert np.array_equal(np.asarray(xa), np.asarray(xb))


def _assignments(wl, engine, policy):
    """Per-CREATE [pod, node] sequence from a decision-trace replay."""
    res = tracing.replay(wl, engine,
                         lambda _p, pod, nodes: policy(pod, nodes), None)
    rows = tracing.extract_trace(res)
    return res, rows, [[r["pod"], r["node"]] for r in rows
                       if r["kind"] == "CREATE"]


# ------------------------------------------------------------- generator

FULL_SPEC = ScenarioSpec(name="all", seed=5, arrival_jitter_frac=0.02,
                         demand_scale=1.1, gpu_milli_scale=0.9,
                         pod_mix_swap_frac=0.3, fault_nodes=2)


def test_perturb_deterministic_byte_identical():
    base = synthetic_workload(4, 24, seed=3)
    _assert_trees_identical(perturb_workload(base, FULL_SPEC),
                            perturb_workload(base, FULL_SPEC))


def test_perturb_seed_changes_output():
    base = synthetic_workload(4, 24, seed=3)
    a = perturb_workload(base, FULL_SPEC)
    b = perturb_workload(base, dataclasses.replace(FULL_SPEC, seed=6))
    assert not np.array_equal(np.asarray(a.pods.creation_time),
                              np.asarray(b.pods.creation_time))


def test_perturb_rejects_faulted_base():
    base = synthetic_workload(2, 8, seed=0)
    faulted = perturb_workload(base, ScenarioSpec(name="f", fault_nodes=1))
    assert faulted.faults is not None
    with pytest.raises(ValueError, match="already carries fault events"):
        perturb_workload(faulted, ScenarioSpec(name="g"))


def test_identity_spec_is_base_with_no_faults():
    base = synthetic_workload(3, 12, seed=1)
    out = perturb_workload(base, ScenarioSpec(name="base"))
    assert out.faults is None
    _assert_trees_identical(
        dataclasses.replace(out, faults=None),
        dataclasses.replace(base, faults=None))


def test_make_fault_events_sorts_pads_validates():
    fe = make_fault_events([(50, 1, KIND_NODE_UP), (10, 1, KIND_NODE_DOWN)],
                           pad_to=4)
    assert np.asarray(fe.time)[:2].tolist() == [10, 50]
    assert np.asarray(fe.mask).tolist() == [True, True, False, False]
    assert np.asarray(fe.time)[2:].tolist() == [np.iinfo(np.int32).max] * 2
    assert make_fault_events([]) is None
    with pytest.raises(ValueError, match="not NODE_DOWN/NODE_UP"):
        make_fault_events([(5, 0, 99)])


def test_fault_events_paired_and_in_span():
    base = synthetic_workload(4, 40, seed=3)
    ev = fault_events_for(base, ScenarioSpec(name="f", seed=9, fault_nodes=2))
    downs = [e for e in ev if e[2] == KIND_NODE_DOWN]
    ups = [e for e in ev if e[2] == KIND_NODE_UP]
    assert len(downs) == 2 and len(ups) == 2
    assert {d[1] for d in downs} == {u[1] for u in ups}
    for (td, nd, _), (tu, nu, _) in zip(sorted(downs, key=lambda e: e[1]),
                                        sorted(ups, key=lambda e: e[1])):
        assert tu > td


# ----------------------------------------------------------------- suite

def test_suite_registry_lists_default8():
    suites = list_suites()
    assert suites["default8"]["size"] == 8
    assert "base" in suites["default8"]["scenarios"]
    assert suites["smoke3"]["size"] == 3


def test_get_suite_unknown_name_raises():
    with pytest.raises(ValueError, match="unknown scenario suite"):
        get_suite("nope", synthetic_workload(2, 8, seed=0))


def test_suite_deterministic_and_uniformly_padded():
    base = synthetic_workload(4, 24, seed=3)
    s1 = get_suite("default8", base)
    s2 = get_suite("default8", base)
    assert s1.names == s2.names
    for wa, wb in zip(s1.workloads, s2.workloads):
        _assert_trees_identical(wa, wb)
    # every scenario carries a FaultEvents of the SAME padded length so the
    # suite stacks under vmap (parallel.traces.stack_traces requirement)
    shapes = {np.asarray(w.faults.time).shape for w in s1.workloads}
    assert shapes == {(s1.fault_pad,)}
    desc = s1.describe()
    assert desc["suite"] == "default8"
    assert len(desc["scenarios"]) == 8


# ------------------------------------------------------- cordon semantics

def _cordon_workload():
    """2 identical CPU nodes, 3 pods that all prefer node 0 under
    first_fit; node 0 is cordoned during pod 1's arrival only."""
    nodes = [{"node_id": f"n{i}", "cpu_milli": 4000, "memory_mib": 8000,
              "gpus": []} for i in range(2)]
    pods = [{"pod_id": f"p{i}", "cpu_milli": 500, "memory_mib": 500,
             "num_gpu": 0, "gpu_milli": 0, "creation_time": t,
             "duration_time": 500}
            for i, t in enumerate([0, 20, 60])]
    wl = make_workload(nodes, pods, pad_nodes_to=2, pad_gpus_to=1,
                       pad_pods_to=4)
    faults = make_fault_events([(15, 0, KIND_NODE_DOWN),
                                (50, 0, KIND_NODE_UP)])
    return wl, dataclasses.replace(wl, faults=faults)


@pytest.mark.parametrize("engine", ["exact", "flat"])
def test_cordon_reroutes_then_recovers(engine):
    clean, faulted = _cordon_workload()
    _, _, base_assign = _assignments(clean, engine, zoo.first_fit())
    assert base_assign == [[0, 0], [1, 0], [2, 0]]
    res, rows, assign = _assignments(faulted, engine, zoo.first_fit())
    # pod 1 (t=20) arrives inside the [15, 50) window: node 0 is cordoned,
    # first_fit falls through to node 1; pod 2 (t=60) lands on node 0 again
    assert assign == [[0, 0], [1, 1], [2, 0]]
    assert int(res.scheduled_pods) == 3
    # fault flips appear as trace rows with the new kinds
    kinds = [r["kind"] for r in rows]
    assert kinds.count("NODE_DOWN") == 1 and kinds.count("NODE_UP") == 1
    assert kinds.index("NODE_DOWN") < kinds.index("NODE_UP")


def test_cordon_does_not_evict_running_pods():
    clean, faulted = _cordon_workload()
    res, rows, assign = _assignments(faulted, "exact", zoo.first_fit())
    # pod 0 is RUNNING on node 0 when it goes down at t=15; it keeps its
    # placement (no eviction) and node 0's cpu stays committed through the
    # window — visible as free_cpu on the NODE_DOWN row
    down = next(r for r in rows if r["kind"] == "NODE_DOWN")
    assert assign[0] == [0, 0]
    assert down["free_cpu"] == 2 * 4000 - 500


def test_fused_engine_rejects_fault_workloads():
    from fks_tpu.sim import fused

    _, faulted = _cordon_workload()
    with pytest.raises(ValueError, match="not supported in the fused"):
        fused.make_fused_population_run(faulted)


# --------------------------------------------------------- golden fixture

@pytest.fixture(scope="module")
def golden_fault():
    with open(FIXTURES / "golden_scenario_fault.json") as f:
        return json.load(f)


@pytest.fixture(scope="module")
def golden_fault_workload(golden_fault):
    base = synthetic_workload(**golden_fault["workload"])
    return perturb_workload(base, ScenarioSpec(**golden_fault["spec"]))


def test_golden_fault_timeline_regenerates(golden_fault,
                                           golden_fault_workload):
    fe = golden_fault_workload.faults
    m = np.asarray(fe.mask)
    got = [{"time": int(t), "node": int(nd), "kind": int(k)}
           for t, nd, k in zip(np.asarray(fe.time)[m],
                               np.asarray(fe.node)[m],
                               np.asarray(fe.kind)[m])]
    assert got == golden_fault["fault_timeline"]


@pytest.mark.parametrize("engine", ["exact", "flat"])
@pytest.mark.parametrize("policy", ["first_fit", "best_fit"])
def test_golden_fault_pin(golden_fault, golden_fault_workload, engine,
                          policy):
    pin = golden_fault["policies"][policy]
    res, rows, assign = _assignments(golden_fault_workload, engine,
                                     zoo.ZOO[policy]())
    assert abs(float(res.policy_score) - pin["policy_score"]) <= 1e-5
    assert int(res.scheduled_pods) == pin["scheduled_pods"]
    assert int(res.events_processed) == pin["events_processed"]
    assert assign == pin["assignments"]
    fault_rows = sum(1 for r in rows
                     if r["kind"] in ("NODE_DOWN", "NODE_UP"))
    assert fault_rows == pin["fault_rows"]


def test_golden_fault_assignments_are_fault_sensitive(golden_fault,
                                                      golden_fault_workload):
    # The pinned score alone cannot catch a broken cordon (aggregate
    # utilization doesn't see pod relocation between equal nodes); the
    # assignment vector must genuinely differ from a no-fault run of the
    # same perturbed demand.
    spec = ScenarioSpec(**golden_fault["spec"])
    nofault = perturb_workload(synthetic_workload(**golden_fault["workload"]),
                               dataclasses.replace(spec, fault_nodes=0))
    _, _, clean = _assignments(nofault, "exact", zoo.first_fit())
    pinned = golden_fault["policies"]["first_fit"]["assignments"]
    assert clean != pinned
    diffs = sum(1 for a, b in zip(clean, pinned) if a != b)
    assert diffs >= 5


# -------------------------------------------------- vmapped robust fitness

@pytest.fixture(scope="module")
def small_suite():
    return get_suite("smoke3", synthetic_workload(4, 24, seed=3))


def test_suite_eval_matches_sequential(small_suite):
    params = parametric.seed_weights("best_fit")
    per = np.asarray(make_suite_eval(small_suite)(params).policy_score)
    assert per.shape == (3,)
    pol = parametric.as_policy(params)
    mod = get_engine("exact")
    for i, wl in enumerate(small_suite.workloads):
        ref = float(mod.simulate(wl, pol, SimConfig()).policy_score)
        assert abs(float(per[i]) - ref) <= 1e-6


def test_suite_eval_exact_vs_flat_parity(small_suite):
    params = parametric.seed_weights("best_fit")
    exact = np.asarray(
        make_suite_eval(small_suite, engine="exact")(params).policy_score)
    flat = np.asarray(
        make_suite_eval(small_suite, engine="flat")(params).policy_score)
    assert np.max(np.abs(exact - flat)) <= 1e-5
    # suite index 2 ("fault1") is the fault-injected lane
    assert small_suite.names[2] == "fault1"
    assert small_suite.workloads[2].faults is not None


@pytest.mark.parametrize("policy", ["first_fit", "best_fit"])
def test_truncated_prefix_probe_parity(policy):
    """Budget probe contract (fks_tpu.funsearch.budget): a run stopped at
    ``probe_steps`` reports truncated=True and a fitness computed only
    from the consumed event prefix — identical between the exact and flat
    engines at 1e-5, nonzero (probe scoring lifts the zero-on-truncation
    gate), and distinct from the full-run fitness."""
    wl = synthetic_workload(4, 24, seed=3)
    pol = zoo.ZOO[policy]()
    probe_cfg = SimConfig(max_steps=16, probe_score=True)
    scores = {}
    for eng in ("exact", "flat"):
        res = get_engine(eng).simulate(wl, pol, probe_cfg)
        assert bool(res.truncated)
        assert int(res.events_processed) <= 16
        scores[eng] = float(res.policy_score)
        assert scores[eng] > 0.0
    assert abs(scores["exact"] - scores["flat"]) <= 1e-5
    # same truncated run WITHOUT probe scoring: the finalize gate zeroes it
    gated = get_engine("exact").simulate(wl, pol, SimConfig(max_steps=16))
    assert bool(gated.truncated)
    assert float(gated.policy_score) == 0.0
    # the probe fitness is prefix-only, not the full-run fitness
    full = get_engine("exact").simulate(wl, pol, SimConfig())
    assert not bool(full.truncated)
    assert abs(scores["exact"] - float(full.policy_score)) > 1e-6
    # probe scoring changes NOTHING on a run that finishes: same config
    # minus the step cap must reproduce the ungated full-run score
    done = get_engine("exact").simulate(wl, pol, SimConfig(probe_score=True))
    assert float(done.policy_score) == pytest.approx(
        float(full.policy_score), abs=1e-9)


def test_suite_population_eval_lane_isolation(small_suite):
    pop = parametric.init_population(jax.random.PRNGKey(0), 4, noise=0.3)
    per = np.asarray(
        make_suite_eval(small_suite, population=True)(pop).policy_score)
    assert per.shape == (4, 3)
    # each candidate lane must equal its own single-candidate eval
    single = make_suite_eval(small_suite)
    for c in range(4):
        params_c = jax.tree_util.tree_map(lambda x: x[c], pop)
        ref = np.asarray(single(params_c).policy_score)
        np.testing.assert_allclose(per[c], ref, atol=1e-6)


def test_sharded_suite_eval_matches_unsharded(small_suite):
    from fks_tpu.parallel.mesh import population_mesh

    mesh = population_mesh()
    pop = parametric.init_population(jax.random.PRNGKey(1), 8, noise=0.3)
    rc = RobustConfig(aggregation="cvar", cvar_alpha=0.5)
    ev = make_sharded_suite_eval(small_suite, mesh, rc=rc, elite_k=3)
    robust, per, elite_idx, elite_scores = ev(pop, 8)
    ref_per = np.asarray(
        make_suite_eval(small_suite, population=True)(pop).policy_score)
    ref_robust = np.asarray(aggregate(ref_per, rc))
    np.testing.assert_allclose(np.asarray(per), ref_per, atol=1e-6)
    np.testing.assert_allclose(np.asarray(robust), ref_robust, atol=1e-6)
    order = np.argsort(-ref_robust, kind="stable")[:3]
    np.testing.assert_allclose(np.asarray(elite_scores),
                               ref_robust[order], atol=1e-6)
    assert set(np.asarray(elite_idx).tolist()) == set(order.tolist())


# ------------------------------------------------------------ aggregation

def test_aggregate_modes():
    s = np.array([1.0, 4.0, 2.0, 3.0])
    assert float(aggregate(s, RobustConfig("mean"))) == pytest.approx(2.5)
    assert float(aggregate(s, RobustConfig("min"))) == pytest.approx(1.0)
    # cvar alpha=0.5 over 4 scenarios -> mean of the 2 worst
    assert float(aggregate(s, RobustConfig("cvar", cvar_alpha=0.5))
                 ) == pytest.approx(1.5)
    # tiny alpha degenerates to min (k clamps to 1)
    assert float(aggregate(s, RobustConfig("cvar", cvar_alpha=1e-6))
                 ) == pytest.approx(1.0)
    w = RobustConfig("mean", weights=(1.0, 0.0, 0.0, 1.0))
    assert float(aggregate(s, w)) == pytest.approx(2.0)
    # batched: aggregation folds the TRAILING axis
    b = np.stack([s, s + 1])
    np.testing.assert_allclose(np.asarray(aggregate(b, RobustConfig("min"))),
                               [1.0, 2.0])


def test_robust_config_validation():
    with pytest.raises(ValueError, match="unknown aggregation"):
        RobustConfig("median")
    with pytest.raises(ValueError, match="not in"):
        RobustConfig("cvar", cvar_alpha=0.0)
    with pytest.raises(ValueError, match="weights only apply"):
        RobustConfig("min", weights=(1.0, 2.0))
    with pytest.raises(ValueError, match="weights for"):
        aggregate(np.ones(3), RobustConfig("mean", weights=(1.0, 2.0)))


# --------------------------------------------------- evaluator / evolution

def _micro_workload():
    from tests.test_engine_micro import micro_workload
    return micro_workload()


def test_code_evaluator_suite_breakdown():
    from fks_tpu.funsearch import CodeEvaluator, seed_policies

    wl = _micro_workload()
    suite = get_suite("smoke3", wl)
    ev = CodeEvaluator(wl, suite=suite, robust=RobustConfig("min"))
    rec = ev.evaluate_one(next(iter(seed_policies().values())))
    assert rec.aggregation == "min"
    assert len(rec.scenario_scores) == 3
    assert rec.score == pytest.approx(min(rec.scenario_scores), abs=1e-6)
    assert rec.score > 0


def test_code_evaluator_suite_rejects_fused_engine():
    wl = _micro_workload()
    suite = get_suite("smoke3", wl)
    from fks_tpu.funsearch import CodeEvaluator

    with pytest.raises(ValueError, match="fused"):
        CodeEvaluator(wl, engine="fused", suite=suite)


def test_evolution_with_suite_persists_breakdown(tmp_path):
    from fks_tpu.funsearch import EvolutionConfig, FakeLLM
    from fks_tpu.funsearch import evolution as evo

    cfg = EvolutionConfig(population_size=6, generations=1, elite_size=2,
                          candidates_per_generation=3, max_workers=1,
                          seed=7, early_stop_threshold=1.1,
                          scenario_suite="smoke3",
                          robust_aggregation="cvar", robust_cvar_alpha=0.5)
    fs = evo.run(_micro_workload(), cfg, backend=FakeLLM(seed=7),
                 log=lambda _m: None)
    assert fs.evaluator.suite is not None
    assert fs.evaluator.robust.aggregation == "cvar"
    stats = fs.history[-1]
    assert stats.scenario_suite == "smoke3"
    assert stats.robust_aggregation == "cvar"
    assert len(stats.best_scenario_scores) == 3
    path = fs.save_best_policy(str(tmp_path / "discovered"))
    with open(path) as f:
        champ = json.load(f)
    assert champ["scenario_suite"] == "smoke3"
    assert champ["aggregation"] == "cvar"
    assert set(champ["scenario_scores"]) == {"base", "jitter", "fault1"}
    per = np.array([champ["scenario_scores"][n]
                    for n in fs.evaluator.suite.names])
    rc = RobustConfig("cvar", cvar_alpha=0.5)
    assert champ["score"] == pytest.approx(float(aggregate(per, rc)),
                                           abs=1e-5)


# ------------------------------------------------------------ cli / schema

def test_cli_scenarios_lists_suites(capsys):
    from fks_tpu import cli

    assert cli.main(["scenarios"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["default8"]["size"] == 8


def test_cli_scenarios_unknown_suite_errors(monkeypatch, capsys):
    from fks_tpu import cli

    monkeypatch.setattr(cli, "_parse_workload",
                        lambda args: ("micro", _micro_workload()))
    assert cli.main(["scenarios", "--suite", "nope"]) == 2


def test_cli_scenarios_describe_and_schema(monkeypatch, capsys, tmp_path):
    from fks_tpu import cli

    monkeypatch.setattr(cli, "_parse_workload",
                        lambda args: ("micro", _micro_workload()))
    run_dir = tmp_path / "run"
    rc = cli.main(["scenarios", "--suite", "smoke3", "--scenario", "2",
                   "--run-dir", str(run_dir)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["name"] == "fault1"
    assert any(e["kind"] == "NODE_DOWN" for e in out["fault_timeline"])
    # the flight-recorder output (scenario_suite metric record) must pass
    # the schema gate that tools/run_full_suite.py enforces
    chk = subprocess.run(
        [sys.executable, str(REPO / "tools" / "check_jsonl_schema.py"),
         "--run-dir", str(run_dir)], capture_output=True, text=True)
    assert chk.returncode == 0, chk.stdout + chk.stderr
